"""Multi-tenant scheduling: fair-share DRR, quotas, priority preemption.

The controller's per-tenant queue groups (ray_tpu/_private/tenants.py +
Controller._try_dispatch_locked) are pinned here end-to-end:

- two saturating tenants' steady-state dispatch shares track the
  configured weights within 10%;
- an over-quota tenant PARKS at lease grant (no autoscale hint) and
  resumes when the quota is raised;
- a starved higher-priority tenant drain-migrates a lower-priority gang
  (zero failed tasks, restart budget uncharged) via the creation-lease
  re-placement path — driven against the scripted FakeAgent harness from
  test_actor_lease, so every wire interaction is the real protocol;
- tenant identity propagates to nested submits;
- autoscaler demand is attributed per tenant;
- head-restart snapshots round-trip configured tenant policy;
- the new ops are chaos-injectable through RAY_testing_rpc_failure.
"""

import time

import pytest

import ray_tpu
from ray_tpu.util.state.api import set_tenant_quota, tenant_stats

from tests.test_actor_lease import FakeAgent, _controller, _wait


def _rows():
    return {r["tenant"]: r for r in tenant_stats()}


@pytest.fixture
def thread_cluster():
    def start(num_cpus=2, **config):
        ray_tpu.init(num_cpus=num_cpus, mode="thread", config=config or None)

    yield start
    ray_tpu.shutdown()


# --------------------------------------------------------------- fair share


def test_two_tenant_saturation_shares_follow_weights(thread_cluster):
    """Saturate 2 CPU slots from two tenants with 3:1 weights: the DRR pop
    must keep steady-state dispatch shares within 10% of the configured
    split (24:8 out of every 32)."""
    thread_cluster(num_cpus=2)
    set_tenant_quota("heavy", weight=3.0)
    set_tenant_quota("light", weight=1.0)

    @ray_tpu.remote(num_cpus=1)
    def work():
        time.sleep(0.02)
        return 1

    n = 60
    refs = []
    for _ in range(n):
        refs.append(work.options(tenant="heavy").remote())
        refs.append(work.options(tenant="light").remote())

    def total_dispatched():
        rows = _rows()
        return (
            rows.get("heavy", {}).get("dispatched", 0)
            + rows.get("light", {}).get("dispatched", 0)
        )

    # sample mid-drain, while BOTH tenants still have queued work (heavy
    # exhausts its 60 only once ~80 total have dispatched at a 3:1 ratio)
    _wait(lambda: total_dispatched() >= 40, msg="steady-state dispatches")
    rows = _rows()
    h = rows["heavy"]["dispatched"]
    l = rows["light"]["dispatched"]
    share = h / (h + l)
    # configured share 0.75; within 10% relative
    assert 0.675 <= share <= 0.825, f"heavy share {share:.3f} ({h}:{l})"

    assert ray_tpu.get(refs, timeout=120) == [1] * (2 * n)
    # charge/credit symmetry: all work done -> both tenants' usage drains
    _wait(
        lambda: not _rows()["heavy"]["usage"]
        and not _rows()["light"]["usage"],
        msg="tenant usage returns to zero",
    )


def test_nested_submit_inherits_tenant(thread_cluster):
    """A task's nested submits bill to the parent's tenant — the whole
    task tree stays in one fair-share queue group."""
    thread_cluster(num_cpus=2)
    # configured tenants persist after their work drains (unconfigured
    # idle ones are reaped — see test_idle_unconfigured_tenant_reaped)
    set_tenant_quota("nest", weight=1.0)

    @ray_tpu.remote(num_cpus=1)
    def child():
        return 1

    @ray_tpu.remote(num_cpus=1)
    def parent():
        return ray_tpu.get(child.remote())

    assert ray_tpu.get(parent.options(tenant="nest").remote(), timeout=60) == 1
    assert _rows()["nest"]["dispatched"] >= 2  # parent AND child


def test_idle_unconfigured_tenant_reaped(thread_cluster):
    """Auto-created tenants (per driver/job) vanish from the registry once
    idle — nothing queued, nothing charged, no configured policy — so a
    long-lived head's scheduler state stays bounded. Configured tenants
    persist."""
    thread_cluster(num_cpus=2)
    set_tenant_quota("keeper", weight=2.0)

    @ray_tpu.remote(num_cpus=1)
    def one():
        return 1

    assert ray_tpu.get(one.options(tenant="ephemeral").remote(), timeout=60) == 1
    from tests.test_actor_lease import _wait as wait

    wait(
        lambda: "ephemeral" not in _rows(),
        msg="idle unconfigured tenant reaped",
    )
    assert "keeper" in _rows()


# -------------------------------------------------------------------- quota


def test_quota_parks_and_resumes_on_raise(thread_cluster):
    """An over-quota tenant's work parks at lease grant (usage never
    exceeds the cap, no autoscale demand is advertised) and resumes the
    moment the quota is raised."""
    thread_cluster(num_cpus=4)
    set_tenant_quota("capped", quota={"CPU": 1.0})

    @ray_tpu.remote(num_cpus=1)
    def nap():
        time.sleep(0.4)
        return "done"

    refs = [nap.options(tenant="capped").remote() for _ in range(3)]
    _wait(
        lambda: _rows()["capped"]["usage"].get("CPU") == 1.0
        and _rows()["capped"]["queued"] == 2,
        msg="two tasks parked behind the CPU=1 cap",
    )
    row = _rows()["capped"]
    # counts TASKS that parked (not scheduler wakeups): at most the two
    # queued tasks can have parked by now
    assert 1 <= row["quota_parked"] <= 2
    # parked-over-quota demand must NOT drive the autoscaler
    assert row["pending_demand"] == []
    ctrl = _controller()
    assert not any(t == "capped" for (t, _s) in ctrl.pending_demand)
    # a fully quota-parked tenant contends for nothing: it must not cost
    # other tenants the pipelining fast path (and a disjoint-resource
    # backlog would not contend for CPU leases either)
    with ctrl.lock:
        assert not ctrl._tenant_contending(
            ctrl.tenants["capped"], {"CPU": 1.0}
        )

    set_tenant_quota("capped", quota={"CPU": 3.0})
    # both parked tasks admit (>= 2 concurrent proves the resume, whatever
    # the first task's completion raced to)
    _wait(
        lambda: _rows()["capped"]["usage"].get("CPU", 0.0) >= 2.0,
        msg="parked work resumed after quota raise",
    )
    assert ray_tpu.get(refs, timeout=60) == ["done"] * 3


# -------------------------------------------------- priority preemption


@pytest.fixture
def preempt_cluster():
    ray_tpu.init(
        num_cpus=1,
        mode="process",
        config={"tcp_port": 0, "preemption_wait_s": 0.3},
    )
    agents: list = []

    def add(resources):
        agent = FakeAgent(_controller(), resources)
        agents.append(agent)
        _wait(
            lambda: agent.node_id in _controller().agents,
            msg="fake agent registration",
        )
        return agent

    yield add
    for a in agents:
        a.close()
    ray_tpu.shutdown()


@ray_tpu.remote(resources={"slot": 1}, max_restarts=2)
class _Pin:
    def ping(self):
        return "pong"


def test_priority_preemption_drain_migrates_low_priority_gang(preempt_cluster):
    """A starved high-priority tenant drains a low-priority restartable
    gang member via the creation-lease migration path: zero failed tasks,
    restart budget uncharged, the victim queues (never dies) and re-places
    once capacity frees."""
    ctrl = _controller()
    a1 = preempt_cluster({"CPU": 1, "slot": 1})
    a2 = preempt_cluster({"CPU": 1, "slot": 1})
    by_node = {a1.node_id: a1, a2.node_id: a2}

    # low-priority gang fills every slot
    low = [_Pin.options(tenant="batch").remote() for _ in range(2)]
    _wait(lambda: len(a1.leases) + len(a2.leases) == 2, msg="gang leases")
    for agent in (a1, a2):
        for lease in agent.leases:
            agent.place(lease)
    for actor in low:
        _wait(
            lambda a=actor: ctrl.actors[a._actor_id].state == "ALIVE",
            msg="gang ALIVE",
        )
    assert ray_tpu.get([a.ping.remote() for a in low], timeout=30) == [
        "pong",
        "pong",
    ]

    # a high-priority tenant arrives with nowhere to go
    high = _Pin.options(tenant="urgent", priority=5).remote()
    _wait(lambda: a1.killed or a2.killed, msg="preemption kill", timeout=30)
    kills = list(a1.killed) + list(a2.killed)
    assert len(kills) == 1  # smallest victim set: exactly one gang member
    victim_agent = a1 if a1.killed else a2
    victim = next(
        a
        for a in low
        if ctrl.actors[a._actor_id].state in ("RESTARTING", "PENDING")
    )
    survivor = next(a for a in low if a is not victim)

    # the freed slot must serve the HIGH-priority creation first (priority
    # tier beats the victim's re-place in the same queue round)
    _wait(
        lambda: any(
            lease.spec.actor_id == high._actor_id
            for lease in victim_agent.leases
        ),
        msg="high-priority lease on the freed node",
    )
    high_lease = next(
        lease
        for lease in victim_agent.leases
        if lease.spec.actor_id == high._actor_id
    )
    victim_agent.place(high_lease)
    _wait(
        lambda: ctrl.actors[high._actor_id].state == "ALIVE",
        msg="high-priority actor ALIVE",
    )

    vstate = ctrl.actors[victim._actor_id]
    # controlled migration: the restart budget is NOT charged and the
    # victim is queued, not dead
    assert vstate.restarts_left == 2
    assert vstate.state == "RESTARTING"
    # zero failed tasks: a call queued on the displaced victim survives the
    # migration (held, replayed on the new incarnation) ...
    pending_ping = victim.ping.remote()
    # ... and the survivor keeps serving throughout
    assert ray_tpu.get(survivor.ping.remote(), timeout=30) == "pong"

    # read the arbitration counters while "urgent" still holds its slot
    # (an idle unconfigured tenant is reaped from the registry)
    rows = _rows()
    assert rows["urgent"]["preemptions"] == 1
    assert rows["batch"]["preempted"] == 1
    events = [e["event"] for e in ctrl.task_events]
    # one starved head == one victim, end to end: later scheduler rounds
    # must not have drained the second gang member too
    assert events.count("PREEMPTED") == 1
    assert ctrl.actor_creation_stats["preempt_migrations"] == 1

    # capacity frees -> the victim re-places through the normal lease path
    before = {
        agent: len(agent.leases) for agent in (a1, a2)
    }
    ray_tpu.kill(high)
    _wait(
        lambda: any(
            len(agent.leases) > before[agent]
            and agent.leases[-1].spec.actor_id == victim._actor_id
            for agent in (a1, a2)
        ),
        msg="victim re-lease after capacity freed",
    )
    agent = next(
        ag
        for ag in (a1, a2)
        if len(ag.leases) > before[ag]
        and ag.leases[-1].spec.actor_id == victim._actor_id
    )
    agent.place(agent.leases[-1])
    assert ray_tpu.get(pending_ping, timeout=30) == "pong"


def test_starvation_clock_survives_sibling_dispatches(preempt_cluster):
    """A starved head's preemption clock belongs to THAT head: a sibling
    shape of the same tenant dispatching successfully every round must
    not keep resetting it (priority inversion forever), and victim
    selection must skip actors whose holds contribute nothing to the
    starved demand — the CPU-only bystander survives, only the slot
    holder migrates."""
    ctrl = _controller()
    # generous CPU so the slot stays the only unmet dimension; "bslot"
    # pins the bystander onto the agent (the head also has a CPU)
    agent = preempt_cluster({"CPU": 6, "slot": 1, "bslot": 1})

    @ray_tpu.remote(num_cpus=1, resources={"bslot": 1}, max_restarts=2)
    class CpuOnly:
        def ping(self):
            return "pong"

    # low-priority: a cheap CPU-only bystander AND the slot holder
    bystander = CpuOnly.options(tenant="batch").remote()
    holder = _Pin.options(tenant="batch").remote()
    _wait(lambda: len(agent.leases) == 2, msg="low-priority leases")
    for lease in agent.leases:
        agent.place(lease)
    for a in (bystander, holder):
        _wait(
            lambda a=a: ctrl.actors[a._actor_id].state == "ALIVE",
            msg="low-priority ALIVE",
        )

    # urgent tenant: the slot head starves while its own CPU-task stream
    # keeps dispatching (leased + instantly completed by the fake agent)
    high = _Pin.options(tenant="urgent", priority=5).remote()

    @ray_tpu.remote(num_cpus=1)
    def cpu_task():
        return 1

    deadline = time.monotonic() + 8.0
    while time.monotonic() < deadline and not agent.killed:
        cpu_task.options(tenant="urgent", priority=5).remote()
        time.sleep(0.05)
    assert agent.killed, "sibling dispatches starved out the preemption"
    # smallest USEFUL victim set: the CPU-only bystander (which frees no
    # slot) is never drained — exactly one kill, the slot holder's worker
    time.sleep(0.5)
    assert len(agent.killed) == 1
    assert ctrl.actors[bystander._actor_id].state == "ALIVE"
    assert ctrl.actors[holder._actor_id].state in ("RESTARTING", "PENDING")


def test_no_preemption_within_one_priority_tier(preempt_cluster):
    """Equal-priority starvation never preempts: the newcomer queues."""
    ctrl = _controller()
    agent = preempt_cluster({"CPU": 1, "slot": 1})
    holder = _Pin.options(tenant="t1").remote()
    _wait(lambda: agent.leases, msg="lease")
    agent.place(agent.leases[0])
    _wait(
        lambda: ctrl.actors[holder._actor_id].state == "ALIVE", msg="ALIVE"
    )

    waiter = _Pin.options(tenant="t2").remote()
    time.sleep(1.2)  # >> preemption_wait_s
    assert not agent.killed
    assert ctrl.actors[holder._actor_id].state == "ALIVE"
    assert ctrl.actors[waiter._actor_id].state == "PENDING"


# ------------------------------------------------- demand attribution


def test_pending_demand_attributes_tenant(thread_cluster):
    """Unplaceable demand reaches the autoscaler tagged with the tenant
    driving it (per-tenant scale-up attribution + dashboard view)."""
    thread_cluster(num_cpus=1)

    @ray_tpu.remote(resources={"TPU": 4.0})
    def big():
        return 1

    big.options(tenant="tpu-team").remote()

    def demanded():
        state = _controller()._dispatch_request("autoscaler_state", None)
        return [
            d
            for d in state["pending_demand"]
            if d["tenant"] == "tpu-team"
            and d["resources"].get("TPU") == 4.0
        ]

    _wait(lambda: demanded(), msg="tenant-attributed demand")
    row = _rows()["tpu-team"]
    assert any(d.get("TPU") == 4.0 for d in row["pending_demand"])


# ---------------------------------------------------- snapshot round trip


def test_head_restart_roundtrips_tenant_state(tmp_path):
    """Configured tenant policy (weights/quota/priority) survives a head
    restart through the state snapshot."""
    snap = str(tmp_path / "gcs-tenants.pkl")
    ray_tpu.init(
        num_cpus=2, mode="thread", config={"gcs_snapshot_path": snap}
    )
    try:
        set_tenant_quota(
            "gold", quota={"CPU": 2.0}, weight=2.5, priority=3
        )
        set_tenant_quota("bronze", weight=0.5)
    finally:
        ray_tpu.shutdown()  # final synchronous snapshot flush

    ray_tpu.init(
        num_cpus=2, mode="thread", config={"gcs_snapshot_path": snap}
    )
    try:
        rows = _rows()
        gold = rows["gold"]
        assert gold["weight"] == 2.5
        assert gold["priority"] == 3
        assert gold["quota"] == {"CPU": 2.0}
        assert gold["configured"]
        assert rows["bronze"]["weight"] == 0.5
    finally:
        ray_tpu.shutdown()


# ------------------------------------------------------------ fault chaos


def test_tenant_ops_chaos_injectable():
    """The new ops ride the same RAY_testing_rpc_failure table as every
    other controller op (catalog-validated, so a typo'd key would have
    raised at init)."""
    ray_tpu.init(
        num_cpus=1,
        mode="thread",
        config={"testing_rpc_failure": "tenant_stats=1.0"},
    )
    try:
        with pytest.raises(Exception, match="injected rpc failure"):
            tenant_stats()
        # the sibling op is NOT injected and still works
        assert set_tenant_quota("ok-tenant", weight=2.0)["weight"] == 2.0
    finally:
        ray_tpu.shutdown()
