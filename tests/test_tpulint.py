"""tpulint unit + integration tests.

Per-check-family unit tests run the analyzer over small synthetic modules in
tmp_path; the self-detection tests assert the shipped bug shapes (PR 3
seal-through-own-pump, PR 4 proxy blocking call, the rank-divergent gang
shape, the collective-order mismatch, the PR 4 spilled-reply leak) are
flagged in the checked-in fixtures; the whole-tree test asserts the repo is
clean modulo the baseline with all eight families and that a full run stays
under the 30 s budget.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from ray_tpu.devtools.lint import CHECKS, lint_paths
from ray_tpu.devtools.lint import baseline as baseline_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)), "lint_fixtures")


def _lint_src(tmp_path, src, checks=None, name="mod_under_test.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(src))
    return lint_paths([str(p)], checks=checks)


def _by_check(findings):
    out = {}
    for f in findings:
        out.setdefault(f.check, []).append(f)
    return out


# ---------------------------------------------------------------- unit tests


def test_blocking_under_lock_direct(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)

            def good(self):
                with self._lock:
                    x = 1
                time.sleep(1.0)
                return x
        """,
    )
    hits = _by_check(findings).get("blocking-under-lock", [])
    assert len(hits) == 1
    assert hits[0].qualname.endswith("C.bad")
    assert "time.sleep" in hits[0].message


def test_blocking_under_lock_interprocedural_chain(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading, queue

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = queue.Queue()

            def outer(self):
                with self._lock:
                    self.middle()

            def middle(self):
                self.inner()

            def inner(self):
                return self._q.get()
        """,
    )
    hits = _by_check(findings).get("blocking-under-lock", [])
    assert len(hits) == 1
    assert hits[0].qualname.endswith("C.outer")
    # the witness chain walks down to the primitive
    assert any("inner" in hop or "queue.get" in hop for hop in hits[0].path)


def test_condition_wait_releases_own_lock(tmp_path):
    # cv.wait under ONLY the cv's own lock is the normal idiom — no finding;
    # the same wait while a SECOND lock is held is flagged.
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cv = threading.Condition(self._lock)
                self._other = threading.Lock()

            def fine(self):
                with self._cv:
                    self._cv.wait()

            def bad(self):
                with self._other:
                    with self._cv:
                        self._cv.wait()
        """,
    )
    hits = _by_check(findings).get("blocking-under-lock", [])
    assert len(hits) == 1
    assert hits[0].qualname.endswith("C.bad")
    assert "_other" in hits[0].message


def test_timed_waits_not_flagged_under_lock(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._ev = threading.Event()

            def fine(self):
                with self._lock:
                    self._ev.wait(timeout=0.5)
        """,
    )
    assert _by_check(findings).get("blocking-under-lock", []) == []


def test_lock_order_cycle(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
        """,
    )
    hits = _by_check(findings).get("lock-order", [])
    assert len(hits) == 1
    assert "cycle" in hits[0].message
    assert "_a" in hits[0].message and "_b" in hits[0].message


def test_lock_order_cycle_interprocedural(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def hold_a(self):
                with self._a:
                    self.take_b()

            def take_b(self):
                with self._b:
                    pass

            def hold_b(self):
                with self._b:
                    self.take_a()

            def take_a(self):
                with self._a:
                    pass
        """,
    )
    hits = _by_check(findings).get("lock-order", [])
    assert len(hits) == 1 and "cycle" in hits[0].message


def test_lock_order_self_deadlock_plain_lock(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._rlock = threading.RLock()

            def bad(self):
                with self._lock:
                    self.helper()

            def helper(self):
                with self._lock:
                    pass

            def fine(self):
                with self._rlock:
                    self.rhelper()

            def rhelper(self):
                with self._rlock:
                    pass
        """,
    )
    hits = _by_check(findings).get("lock-order", [])
    assert len(hits) == 1
    assert "self-deadlock" in hits[0].message
    assert hits[0].qualname.endswith("C.bad")


def test_async_stall(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import time, asyncio

        class H:
            def blocking_pick(self):
                time.sleep(0.5)

            async def bad(self):
                self.blocking_pick()

            async def also_bad(self):
                time.sleep(0.1)

            async def fine(self):
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, self.blocking_pick)
        """,
    )
    hits = _by_check(findings).get("async-stall", [])
    quals = sorted(h.qualname.rsplit(".", 1)[1] for h in hits)
    assert quals == ["also_bad", "bad"]


def test_unguarded_shared_state(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._tally = {}
                self._guarded = {}
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                self._tally = dict(x=1)          # no lock (thread side)
                with self._lock:
                    self._guarded = dict(x=1)

            def update(self):
                self._tally = dict(y=2)          # no lock (caller side)
                with self._lock:
                    self._guarded = dict(y=2)
        """,
    )
    hits = _by_check(findings).get("unguarded-shared-state", [])
    assert len(hits) == 1
    assert "_tally" in hits[0].message


def test_shutdown_hygiene(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class Leaky:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def shutdown(self):
                pass  # forgets the join

        class Clean:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def shutdown(self):
                self._t.join(timeout=1.0)

        class CleanViaAlias:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def close(self):
                t = getattr(self, "_t", None)
                if t is not None:
                    t.join(timeout=1.0)

        class CleanViaHelper:
            def __init__(self):
                self._t = threading.Thread(target=self._loop, daemon=True)
                self._t.start()

            def _loop(self):
                pass

            def stop(self):
                locktrace.join_if_alive(self._t, timeout=1.0)
        """,
    )
    hits = _by_check(findings).get("shutdown-hygiene", [])
    assert len(hits) == 1
    assert "Leaky" in hits[0].message


def test_inline_suppression(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def reviewed(self):
                with self._lock:
                    time.sleep(0.01)  # tpulint: disable=blocking-under-lock
        """,
    )
    assert findings == []


def test_finding_fingerprint_is_line_stable(tmp_path):
    src = """
    import threading, time

    class C:
        def __init__(self):
            self._lock = threading.Lock()

        def bad(self):
            with self._lock:
                time.sleep(1.0)
    """
    f1 = _lint_src(tmp_path, src, name="a.py")
    # same code shifted down two lines -> same fingerprint
    f2 = _lint_src(tmp_path, "\n\n" + textwrap.dedent(src), name="a.py")
    assert len(f1) == len(f2) == 1
    assert f1[0].fingerprint == f2[0].fingerprint
    assert f1[0].line != f2[0].line


def test_baseline_roundtrip(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading, time

        class C:
            def __init__(self):
                self._lock = threading.Lock()

            def bad(self):
                with self._lock:
                    time.sleep(1.0)
        """,
    )
    assert len(findings) == 1
    bpath = str(tmp_path / "baseline.json")
    baseline_mod.write(bpath, findings)
    base = baseline_mod.load(bpath)
    new, accepted, stale = baseline_mod.split(findings, base)
    assert new == [] and len(accepted) == 1 and stale == []
    # reasons survive a rewrite
    base[findings[0].fingerprint]["reason"] = "reviewed: example"
    baseline_mod.write(bpath, findings, old=base)
    assert (
        baseline_mod.load(bpath)[findings[0].fingerprint]["reason"]
        == "reviewed: example"
    )
    # a fixed finding shows up as stale
    new, accepted, stale = baseline_mod.split([], baseline_mod.load(bpath))
    assert len(stale) == 1


# ------------------------------------------------- ref-lifecycle (units)


def test_lifecycle_leak_on_exception_edge(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import socket

        def bad():
            s = socket.socket()
            s.bind(("", 0))        # may raise: s leaks
            port = s.getsockname()[1]
            s.close()
            return port

        def good():
            s = socket.socket()
            try:
                s.bind(("", 0))
                return s.getsockname()[1]
            finally:
                s.close()
        """,
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    assert len(hits) == 1
    assert hits[0].qualname.endswith(".bad")
    assert "leaks when" in hits[0].message


def test_lifecycle_leak_on_early_return(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        from multiprocessing import shared_memory

        def bad(name, n):
            seg = shared_memory.SharedMemory(name=name)
            if n == 0:
                return None     # seg stranded
            data = bytes(seg.buf[:n])
            seg.close()
            return data
        """,
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    assert len(hits) == 1
    assert "early return" in hits[0].message


def test_lifecycle_double_release_and_use_after_release(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        from multiprocessing import shared_memory

        def double(name):
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
            seg.unlink()

        def uar(name, n):
            seg = shared_memory.SharedMemory(name=name)
            seg.close()
            return bytes(seg.buf[:n])
        """,
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    msgs = " | ".join(h.message for h in hits)
    assert "released twice" in msgs
    assert "after" in msgs and any("buf" in h.message for h in hits)


def test_lifecycle_escape_and_with_are_clean(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import socket
        from multiprocessing import shared_memory

        class Cache:
            def __init__(self):
                self._segs = {}

            def attach(self, name):
                seg = shared_memory.SharedMemory(name=name)
                self._segs[name] = seg       # ownership transferred
                return seg

        def factory():
            return socket.socket()           # caller owns it

        def managed(name, n):
            with shared_memory.SharedMemory(name=name) as seg:
                return bytes(seg.buf[:n])
        """,
    )
    assert _by_check(findings).get("ref-lifecycle", []) == []


def test_lifecycle_interprocedural_release_helper(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import socket

        def _reap(sock):
            sock.close()

        def fine():
            s = socket.socket()
            try:
                s.bind(("", 0))
                return s.getsockname()[1]
            finally:
                _reap(s)
        """,
    )
    assert _by_check(findings).get("ref-lifecycle", []) == []


def test_lifecycle_dropped_objectref(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import ray_tpu

        def bad(x):
            ray_tpu.put(x)   # ref dropped: dead put

        def good(x):
            ref = ray_tpu.put(x)
            return ref
        """,
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    assert len(hits) == 1 and "dropped" in hits[0].message


def test_lifecycle_suppression_and_baseline_roundtrip(tmp_path):
    src = """
    import socket

    def reviewed():
        s = socket.socket()
        s.bind(("", 0))  # tpulint: disable=ref-lifecycle
        s.close()
    """
    assert _lint_src(tmp_path, src) == []
    findings = _lint_src(
        tmp_path,
        src.replace("  # tpulint: disable=ref-lifecycle", ""),
        name="mod_b.py",
    )
    assert len(findings) == 1
    bpath = str(tmp_path / "lc_baseline.json")
    baseline_mod.write(bpath, findings)
    new, accepted, stale = baseline_mod.split(findings, baseline_mod.load(bpath))
    assert new == [] and len(accepted) == 1 and stale == []


def test_lifecycle_handler_access_with_own_finally_clean(tmp_path):
    """The catching try's OWN finally runs AFTER its handler: handler-side
    access to the handle is valid and must not be a use-after-release."""
    findings = _lint_src(
        tmp_path,
        """
        from multiprocessing import shared_memory

        def f(name, n):
            seg = shared_memory.SharedMemory(name=name)
            try:
                data = decode(n)
            except Exception:
                data = bytes(seg.buf[:1])
            finally:
                seg.close()
            return data

        def decode(n):
            raise ValueError(n)
        """,
    )
    assert _by_check(findings).get("ref-lifecycle", []) == [
    ], [f.render() for f in findings]


def test_lifecycle_nonrelease_call_in_finally_does_not_mask(tmp_path):
    """`log(seg)` in a finally releases nothing — the leak must survive."""
    findings = _lint_src(
        tmp_path,
        """
        from multiprocessing import shared_memory

        def f(name, n):
            seg = shared_memory.SharedMemory(name=name)
            try:
                data = decode(n)
            finally:
                log(seg)
            seg.close()
            return data

        def decode(n):
            raise ValueError(n)

        def log(x):
            pass
        """,
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    assert len(hits) == 1 and "leaks when" in hits[0].message


# ------------------------------------------- collective-uniformity (units)


def test_collective_divergent_in_nested_uniform_branch(tmp_path):
    """A collective on the ELSE arm of an inner uniform if must stay
    visible to the outer rank-divergence check."""
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def bad(grads, rank, use_fast):
            if rank == 0:
                if use_fast:
                    grads = grads * 2
                else:
                    grads = jax.lax.psum(grads, "dp")
            return grads

        def good(grads, rank, use_fast):
            if rank == 0:
                grads = jax.lax.psum(grads, "dp")
            else:
                if use_fast:
                    grads = jax.lax.psum(grads * 2, "dp")
                else:
                    grads = jax.lax.psum(grads * 3, "dp")
            return grads
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1, [f.render() for f in findings]
    assert hits[0].qualname.endswith(".bad")


def test_collective_divergent_branch(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def bad(grads, rank):
            if rank == 0:
                grads = jax.lax.psum(grads, "dp")
            return grads

        def good(grads, rank):
            grads = jax.lax.psum(grads, "dp")
            if rank == 0:
                print(grads)
            return grads
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1
    assert hits[0].qualname.endswith(".bad")
    assert "psum" in hits[0].message and "rank" in hits[0].message


def test_collective_guard_return(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def bad(grads, rank):
            if rank != 0:
                return grads
            return jax.lax.psum(grads, "dp")
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1 and "guard" in hits[0].message


def test_collective_order_mismatch(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def bad(g, a, is_coordinator):
            if is_coordinator:
                g = jax.lax.psum(g, "dp")
                a = jax.lax.all_gather(a, "dp")
            else:
                a = jax.lax.all_gather(a, "dp")
                g = jax.lax.psum(g, "dp")
            return g, a

        def good(g, a, is_coordinator):
            if is_coordinator:
                g = jax.lax.psum(g, "dp")
                a = jax.lax.all_gather(a, "dp")
            else:
                g = jax.lax.psum(g * 2, "dp")
                a = jax.lax.all_gather(a * 2, "dp")
            return g, a
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1
    assert "different orders" in hits[0].message
    assert hits[0].qualname.endswith(".bad")


def test_collective_exception_dependent(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def bad(grads):
            try:
                grads = step(grads)
            except Exception:
                grads = jax.lax.psum(grads, "dp")   # only raising ranks
            return grads

        def step(grads):
            return grads
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1 and "except handler" in hits[0].message


def test_collective_interprocedural_chain(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        class W:
            def __init__(self, rank):
                self.rank = rank

            def bad(self, grads):
                if self.rank == 0:
                    grads = self._sync(grads)
                return grads

            def _sync(self, grads):
                return jax.lax.psum(grads, "dp")
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1
    assert any("_sync" in hop for hop in hits[0].path)


def test_collective_time_divergent_loop(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import time
        import jax

        def bad(grads, deadline):
            while time.monotonic() < deadline:
                grads = jax.lax.psum(grads, "dp")
            return grads
        """,
    )
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1 and "time" in hits[0].message


def test_collective_suppression(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import jax

        def reviewed(grads, rank):
            if rank == 0:
                grads = jax.lax.psum(grads, "dp")  # tpulint: disable=collective-uniformity
            return grads
        """,
    )
    assert _by_check(findings).get("collective-uniformity", []) == []


# ------------------------------------------------ wire-conformance (units)

_WIRE_COMMON = """
    import threading

    class Reply:
        def __init__(self, req_id, payload, error=None):
            self.req_id = req_id
            self.payload = payload
            self.error = error

    class Head:
        def __init__(self):
            self._kv = {}
            self._actors = {}

        def _dispatch_request(self, op, payload):
            if op == "kv_put":
                ns, key, value = payload
                self._kv[(ns, key)] = value
                return None
            if op == "get_named_actor":
                actor = self._actors.get(payload)
                if actor is None:
                    return None
                return (actor, 1)
            raise ValueError(op)

        def _handle_request(self, handle, msg):
            try:
                reply = Reply(msg.req_id, self._dispatch_request(msg.op, msg.payload))
            except Exception as e:
                reply = Reply(msg.req_id, None, error=str(e))
            handle.send(reply)

    class Runtime:
        def __init__(self, conn):
            self._conn = conn
            self._ready = threading.Event()
            self._replies = {}
            self._req_id = 0

        def call_controller(self, op, payload=None):
            self._req_id += 1
            self._conn.send((self._req_id, op, payload))
            self._ready.wait(timeout=30.0)
            return self._replies.pop(self._req_id)
"""


def test_wire_catalog_extraction(tmp_path):
    """Phase 1: handler ops, payload shapes, reply shapes, send sites, and
    forwarding-wrapper helpers are all extracted from the AST."""
    import textwrap as _tw

    from ray_tpu.devtools.lint import analyze, discover
    from ray_tpu.devtools.lint.wire import build_catalog

    p = tmp_path / "wire_mod.py"
    p.write_text(
        _tw.dedent(_WIRE_COMMON)
        + _tw.dedent(
            """
            def _call(op, payload=None):
                rt = Runtime(None)
                return rt.call_controller(op, payload)

            def put_meta(ns, key, value):
                return _call("kv_put", (ns, key, value))
            """
        )
    )
    project = discover([str(p)])
    analyze(project)
    cat = build_catalog(project)
    assert set(cat.handlers) == {"kv_put", "get_named_actor"}
    h = cat.handlers["kv_put"][0]
    assert h.payload_arity == 3 and h.payload_fields == ("ns", "key", "value")
    assert ("none", None) in h.reply_shapes
    h2 = cat.handlers["get_named_actor"][0]
    assert ("none", None) in h2.reply_shapes and ("tuple", 2) in h2.reply_shapes
    # the wrapper `_call` is discovered by the op-forwarding fixed point,
    # so put_meta's literal registers as a send site
    assert any(q.endswith("._call") for q in cat.helpers)
    assert [s.qualname for s in cat.sends["kv_put"]][0].endswith("put_meta")
    # get_named_actor has a handler but no sender -> report-only dead op
    assert cat.dead_ops == ["get_named_actor"]


def test_wire_raise_without_error_reply(tmp_path):
    """A dispatch site that feeds a reply channel without converting raises
    leaves the requester's reader waiting forever — flagged; the converting
    shape in _WIRE_COMMON stays clean."""
    findings = _lint_src(
        tmp_path,
        """
        class Reply:
            def __init__(self, req_id, payload, error=None):
                self.req_id = req_id
                self.payload = payload
                self.error = error

        class Head:
            def _dispatch_request(self, op, payload):
                if op == "ping":
                    return "pong"
                if op == "boom":
                    raise RuntimeError("x")
                raise ValueError(op)

            def _handle_request(self, handle, msg):
                reply = Reply(msg.req_id, self._dispatch_request(msg.op, msg.payload))
                handle.send(reply)
        """,
        checks=["wire-conformance"],
    )
    assert len(findings) == 1, [f.render() for f in findings]
    assert "error-reply conversion" in findings[0].message
    assert findings[0].qualname.endswith("_handle_request")
    clean = _lint_src(tmp_path, _WIRE_COMMON, name="wire_ok.py", checks=["wire-conformance"])
    assert clean == [], [f.render() for f in clean]


def test_wire_unbounded_request_wait(tmp_path):
    findings = _lint_src(
        tmp_path,
        """
        import threading

        class R:
            def __init__(self):
                self._ev = threading.Event()

            def call_controller(self, op, payload=None):
                self._ev.wait()
                return None

            def go(self):
                return self.call_controller("ping")
        """,
        checks=["wire-conformance"],
    )
    assert len(findings) == 1, [f.render() for f in findings]
    assert "untimed" in findings[0].message
    assert findings[0].qualname.endswith("call_controller")


def test_wire_declared_opset_drift(tmp_path):
    import textwrap as _tw

    findings = _lint_src(
        tmp_path,
        'CONTROLLER_OPS = frozenset({"kv_put"})\n' + _tw.dedent(_WIRE_COMMON),
        checks=["wire-conformance"],
    )
    assert len(findings) == 1, [f.render() for f in findings]
    assert "drifted" in findings[0].message
    assert "get_named_actor" in findings[0].message
    clean = _lint_src(
        tmp_path,
        'CONTROLLER_OPS = frozenset({"kv_put", "get_named_actor"})\n'
        + _tw.dedent(_WIRE_COMMON),
        name="wire_set_ok.py",
        checks=["wire-conformance"],
    )
    assert clean == [], [f.render() for f in clean]


def test_wire_agent_only_op(tmp_path):
    """An op the node agent intercepts but the controller does not handle
    breaks head-side workers (they have no agent) — flagged."""
    import textwrap as _tw

    findings = _lint_src(
        tmp_path,
        _tw.dedent(_WIRE_COMMON)
        + _tw.dedent(
            """
            class Agent:
                def _route_worker_msg(self, msg):
                    if msg.op == "kv_put":
                        self._reply_worker(msg, self._kv_put_local, msg.payload)
                        return
                    if msg.op == "node_only_op":
                        self._reply_worker(msg, self._node_thing, msg.payload)
                        return

                def _reply_worker(self, msg, fn, payload):
                    try:
                        reply = Reply(msg.req_id, fn(payload))
                    except Exception as e:
                        reply = Reply(msg.req_id, None, error=str(e))
                    msg.conn.send(reply)

                def _kv_put_local(self, payload):
                    ns, key, value = payload
                    return None

                def _node_thing(self, payload):
                    return None
            """
        ),
        checks=["wire-conformance"],
    )
    assert len(findings) == 1, [f.render() for f in findings]
    assert "node_only_op" in findings[0].message
    assert "head-side workers" in findings[0].message


def test_wire_msg_branch_without_conversion_flagged_standalone(tmp_path):
    """An agent-style (msg.op) branch that sends replies without converting
    raises is flagged even when no param-style surface is in the slice —
    the --changed-only agent-only slice must not go blind."""
    findings = _lint_src(
        tmp_path,
        """
        class Reply:
            def __init__(self, req_id, payload, error=None):
                self.req_id = req_id
                self.payload = payload
                self.error = error

        class Agent:
            def _route_worker_msg(self, conn, msg):
                if msg.op == "shm_create":
                    conn.send(Reply(msg.req_id, self._shm_create(msg.payload)))
                    return
                if msg.op == "pull_chunk":
                    conn.send(Reply(msg.req_id, self._pull_chunk(msg.payload)))
                    return

            def _shm_create(self, payload):
                object_id, size = payload
                return object_id

            def _pull_chunk(self, payload):
                return None
        """,
        checks=["wire-conformance"],
    )
    hits = [f for f in findings if "without converting raises" in f.message]
    assert len(hits) == 2, [f.render() for f in findings]


def test_wire_suppression_and_baseline_roundtrip(tmp_path):
    import textwrap as _tw

    src = _tw.dedent(_WIRE_COMMON) + _tw.dedent(
        """
        def bad(rt):
            return rt.call_controller("kv_putt", ("ns", "k", "v"))  # tpulint: disable=wire-conformance
        """
    )
    assert _lint_src(tmp_path, src, checks=["wire-conformance"]) == []
    findings = _lint_src(
        tmp_path,
        src.replace("  # tpulint: disable=wire-conformance", ""),
        name="wire_b.py",
        checks=["wire-conformance"],
    )
    assert len(findings) == 1 and "kv_putt" in findings[0].message
    bpath = str(tmp_path / "wire_baseline.json")
    baseline_mod.write(bpath, findings)
    new, accepted, stale = baseline_mod.split(findings, baseline_mod.load(bpath))
    assert new == [] and len(accepted) == 1 and stale == []


def test_fixture_wire_typo_flagged():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_wire_typo.py")])
    hits = _by_check(findings).get("wire-conformance", [])
    assert len(hits) == 1, [f.render() for f in findings]
    assert "object_locatons" in hits[0].message
    assert "did you mean" in hits[0].message


def test_fixture_wire_arity_flagged():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_wire_arity.py")])
    hits = _by_check(findings).get("wire-conformance", [])
    assert len(hits) == 1, [f.render() for f in findings]
    assert "2-tuple" in hits[0].message and "3 fields" in hits[0].message
    assert hits[0].qualname.endswith("Agent.register")


def test_fixture_wire_none_reply_flagged():
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_wire_none_reply.py")]
    )
    hits = _by_check(findings).get("wire-conformance", [])
    assert len(hits) == 1, [f.render() for f in findings]
    assert "None" in hits[0].message
    assert hits[0].qualname.endswith("Driver.get_actor")
    assert not any("get_actor_safe" in h.qualname for h in hits)


def test_fixture_wire_clean_has_zero_findings():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_wire_clean.py")])
    assert findings == [], [f.render() for f in findings]


def test_fixture_actor_lease_leak_flagged():
    """The PR 10 lease-protocol shape done wrong: a typo'd actor_placed
    report, an actor_creation_failed payload one field short of the
    handler unpack, and the spawn path stranding the per-lease log handle
    when creation dispatch raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_actor_lease_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "actor_placd" in h.message)
    assert 'did you mean "actor_placed"' in typo.message
    arity = next(h for h in wire if "actor_creation_failed" in h.message)
    assert "4-tuple" in arity.message and "5 fields" in arity.message
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("Spawner.run_lease")
    assert "leaks when" in life[0].message


def test_fixture_actor_lease_clean_has_zero_findings():
    """Same protocol shapes done right (matching ops/arities, guarded
    verdict, finally-credited lease log, declared op set in sync): zero
    findings across every family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_actor_lease_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_tenant_ops_leak_flagged():
    """The PR 11 tenant-protocol shape done wrong: a typo'd tenant_stats
    query, a set_tenant_quota payload one field short of the handler
    unpack, and the admin path stranding the quota-audit log handle when
    validation raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_tenant_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "tenant_statz" in h.message)
    assert 'did you mean "tenant_stats"' in typo.message
    arity = next(h for h in wire if "set_tenant_quota" in h.message)
    assert "3-tuple" in arity.message and "4 fields" in arity.message
    assert arity.qualname.endswith("Admin.set_quota")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("Admin.apply_policy")
    assert "leaks when" in life[0].message


def test_fixture_tenant_ops_clean_has_zero_findings():
    """Same tenant-protocol shapes done right (matching ops/arities,
    guarded maybe-empty stats reply, finally-credited audit log, declared
    op set in sync): zero findings across every family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_tenant_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_batch_ops_leak_flagged():
    """The PR 12 batched-ops shape done wrong: a typo'd submit_batc flush
    (did-you-mean), the flusher unpacking submit_batch's None reply, and
    the flush path stranding the per-batch trace log when delivery
    raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_batch_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "submit_batc" in h.message)
    assert 'did you mean "submit_batch"' in typo.message
    misuse = next(h for h in wire if "unpacked into 2" in h.message)
    assert "submit_batch" in misuse.message and "None" in misuse.message
    assert misuse.qualname.endswith("Coalescer.flush_and_count")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("Coalescer.flush_traced")
    assert "leaks when" in life[0].message


def test_fixture_batch_ops_clean_has_zero_findings():
    """Same batched-ops shapes done right (correct op literal, reply
    guarded/ignored, finally-credited trace log, declared op set in sync):
    zero findings across every family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_batch_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_proxy_ops_leak_flagged():
    """The PR 13 serve-ingress shape done wrong: a typo'd
    report_proxy_statz push (did-you-mean), a 3-tuple report payload
    against the handler's 2-field unpack, and the stats-flush path
    stranding the shed-audit spool when delivery raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_proxy_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "report_proxy_statz" in h.message)
    assert 'did you mean "report_proxy_stats"' in typo.message
    arity = next(
        h for h in wire
        if "report_proxy_stats" in h.message and "statz" not in h.message
    )
    assert "3-tuple" in arity.message and "2 fields" in arity.message
    assert arity.qualname.endswith("ProxyStatsPusher.push_with_port")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("ProxyStatsPusher.flush_window")
    assert "leaks when" in life[0].message


def test_fixture_proxy_ops_clean_has_zero_findings():
    """Same serve-ingress proxy-op shapes done right (matching ops and
    arities, guarded maybe-empty proxy_stats reply, finally-credited
    shed-audit spool, declared op set in sync): zero findings across every
    family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_proxy_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_observe_ops_leak_flagged():
    """The PR 14 observability shape done wrong: a typo'd
    report_observabilty push (did-you-mean), a 3-tuple report payload
    against the handler's 2-field unpack, and the drain-and-ship path
    stranding the span spool when delivery raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_observe_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "report_observabilty" in h.message)
    assert 'did you mean "report_observability"' in typo.message
    arity = next(
        h for h in wire
        if "report_observability" in h.message and "observabilty" not in h.message
    )
    assert "3-tuple" in arity.message and "2 fields" in arity.message
    assert arity.qualname.endswith("ObservabilityShipper.ship_with_dropped")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("ObservabilityShipper.ship_spooled")
    assert "leaks when" in life[0].message


def test_fixture_observe_ops_clean_has_zero_findings():
    """Same observability-plane shapes done right (matching ops and
    arities, guarded maybe-empty cluster_metrics reply, finally-credited
    span spool, declared op set in sync): zero findings across every
    family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_observe_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_recovery_ops_leak_flagged():
    """The PR 15 head-recovery shape done wrong: a typo'd reconcile_repord
    send (did-you-mean), a 3-tuple reconcile_report payload against the
    handler's 2-field unpack, and the rotate-and-compact path stranding
    the WAL segment handle when the snapshot write raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_recovery_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "reconcile_repord" in h.message)
    assert 'did you mean "reconcile_report"' in typo.message
    arity = next(
        h for h in wire
        if "reconcile_report" in h.message and "repord" not in h.message
    )
    assert "3-tuple" in arity.message and "2 fields" in arity.message
    assert arity.qualname.endswith("ReconcilingAgent.reconcile_with_seq")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("Journal.compact")
    assert "leaks when" in life[0].message


def test_fixture_recovery_ops_clean_has_zero_findings():
    """Same recovery-plane shapes done right (matching ops and arities,
    guarded maybe-empty recovery_stats reply, finally-credited WAL segment
    handle, declared op set in sync): zero findings across every family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_recovery_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_fixture_preempt_ops_leak_flagged():
    """The ISSUE 20 preempt-notice shape done wrong: a typo'd
    node_preempt_notise send (did-you-mean), a 4-tuple node_preempt_notice
    payload against the handler's 3-field unpack, and the
    announce-and-audit path stranding the audit log handle when the
    downstream notifier raises."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_preempt_ops_leak.py")]
    )
    wire = _by_check(findings).get("wire-conformance", [])
    assert len(wire) == 2, [f.render() for f in findings]
    typo = next(h for h in wire if "node_preempt_notise" in h.message)
    assert 'did you mean "node_preempt_notice"' in typo.message
    arity = next(
        h for h in wire
        if "node_preempt_notice" in h.message and "notise" not in h.message
    )
    assert "4-tuple" in arity.message and "3 fields" in arity.message
    assert arity.qualname.endswith("PreemptingAgent.announce_with_deadline")
    life = _by_check(findings).get("ref-lifecycle", [])
    assert len(life) == 1, [f.render() for f in findings]
    assert life[0].qualname.endswith("NoticeAudit.announce_and_audit")
    assert "leaks when" in life[0].message


def test_fixture_preempt_ops_clean_has_zero_findings():
    """Same preempt-notice shapes done right (matching op and arity,
    guarded maybe-missing drain_status reply, finally-credited audit
    handle, declared op set in sync): zero findings across every family."""
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_preempt_ops_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_protocol_doc_is_current_and_covers_controller_ops():
    """docs/PROTOCOL.md matches a fresh render of the extracted catalog and
    names every controller op + the agent data-plane surface."""
    from ray_tpu._private import protocol as P
    from ray_tpu.devtools.lint import analyze, discover
    from ray_tpu.devtools.lint.wire import build_catalog, render_protocol_doc

    project = discover([os.path.join(REPO, "ray_tpu")], root=REPO)
    analyze(project)
    rendered = render_protocol_doc(build_catalog(project))
    with open(os.path.join(REPO, "docs", "PROTOCOL.md")) as f:
        checked_in = f.read()
    assert checked_in == rendered, (
        "docs/PROTOCOL.md is stale — regenerate with "
        "`python -m ray_tpu.devtools.lint --write-protocol-doc`"
    )
    for op in sorted(P.CONTROLLER_OPS):
        assert f"`{op}`" in checked_in, f"op {op} missing from PROTOCOL.md"
    for op in sorted(P.AGENT_LOCAL_OPS):
        assert f"| `{op}` | Controller + NodeAgent" in checked_in, op
    assert '`("chunk", object_id_bytes, offset, length)`' in checked_in
    assert "_data_serve" in checked_in


def test_wire_doc_drift_fails_full_tree_runs(tmp_path):
    """A stale protocol doc fails full-tree runs (and only full-tree runs:
    slices see a partial catalog and must not false-positive)."""
    stale = tmp_path / "PROTOCOL.md"
    stale.write_text("# stale\n")
    findings = lint_paths(
        [os.path.join(REPO, "ray_tpu")],
        root=REPO,
        checks=["wire-conformance"],
        config={"protocol_doc": str(stale)},
        full_tree=True,
    )
    assert any("stale" in f.message for f in findings), [
        f.render() for f in findings
    ]
    # same stale doc, but not marked full-tree -> no drift finding
    findings = lint_paths(
        [os.path.join(REPO, "ray_tpu")],
        root=REPO,
        checks=["wire-conformance"],
        config={"protocol_doc": str(stale)},
    )
    assert findings == [], [f.render() for f in findings]


def test_wire_slice_fingerprints_match_full_dir():
    """Wire findings keep the PR 7 property --changed-only rests on: a
    single-file slice yields the same qualnames (hence fingerprints) as a
    directory run over the same root."""
    target = os.path.join(FIXTURES, "fixture_wire_typo.py")
    slice_f = [
        f
        for f in lint_paths([target], root=REPO)
        if f.check == "wire-conformance"
    ]
    full_f = [
        f
        for f in lint_paths([FIXTURES], root=REPO)
        if f.check == "wire-conformance" and "typo" in f.file
    ]
    assert slice_f and full_f
    assert {f.fingerprint for f in slice_f} == {f.fingerprint for f in full_f}


def test_cli_write_protocol_doc_refuses_slices(tmp_path):
    # path slices AND --changed-only (even a clean one, which short-circuits
    # before the doc could be written) must refuse up front
    for argv in (
        [os.path.join(FIXTURES, "fixture_wire_clean.py"), "--write-protocol-doc"],
        ["--changed-only", "--write-protocol-doc"],
    ):
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.devtools.lint", *argv],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 2, (argv, proc.stdout, proc.stderr)
        assert "full-tree" in proc.stderr


# ------------------------------------------------- self-detection fixtures


def test_fixture_seal_through_pump_flagged():
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_seal_through_pump.py")]
    )
    hits = _by_check(findings).get("blocking-under-lock", [])
    assert hits, "the PR 3 deadlock shape must be flagged"
    assert any("_exec_lock" in h.message for h in hits)


def test_fixture_proxy_block_flagged():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_proxy_block.py")])
    hits = _by_check(findings).get("async-stall", [])
    assert hits, "the PR 4 proxy-freeze shape must be flagged"
    assert any("handle_request" in h.qualname for h in hits)


def test_fixture_clean_has_zero_findings():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_clean.py")])
    assert findings == [], [f.render() for f in findings]


def test_fixture_rank_divergent_flagged():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_rank_divergent.py")])
    hits = _by_check(findings).get("collective-uniformity", [])
    quals = {h.qualname.rsplit(".", 1)[1] for h in hits}
    assert {"bad_step", "bad_guard_return", "bad_via_helper"} <= quals, [
        f.render() for f in findings
    ]
    # the interprocedural shape reports the full chain down to the psum
    chained = [h for h in hits if h.qualname.endswith("bad_via_helper")]
    assert chained and any("_sync" in hop for hop in chained[0].path)
    assert not any("good_step" in h.qualname for h in hits)


def test_fixture_order_mismatch_flagged():
    findings = lint_paths([os.path.join(FIXTURES, "fixture_order_mismatch.py")])
    hits = _by_check(findings).get("collective-uniformity", [])
    assert len(hits) == 1, [f.render() for f in findings]
    assert "different orders" in hits[0].message
    assert hits[0].qualname.endswith("bad_step")
    # the path lists both arms' sequences
    assert any("then-arm" in hop for hop in hits[0].path)
    assert any("else-arm" in hop for hop in hits[0].path)


def test_fixture_spilled_reply_leak_flagged():
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_spilled_reply_leak.py")]
    )
    hits = _by_check(findings).get("ref-lifecycle", [])
    msgs = {h.qualname.rsplit(".", 1)[1]: h.message for h in hits}
    assert "leaks when" in msgs.get("read_spilled_reply", ""), msgs
    assert "early return" in msgs.get("read_spilled_reply_early_return", ""), msgs
    assert "released twice" in msgs.get("double_unlink", ""), msgs
    assert "after" in msgs.get("use_after_release", ""), msgs


def test_fixture_lifecycle_clean_has_zero_findings():
    findings = lint_paths(
        [os.path.join(FIXTURES, "fixture_lifecycle_clean.py")]
    )
    assert findings == [], [f.render() for f in findings]


def test_cli_exits_nonzero_on_fixtures():
    for fx in (
        "fixture_seal_through_pump.py",
        "fixture_proxy_block.py",
        "fixture_rank_divergent.py",
        "fixture_order_mismatch.py",
        "fixture_spilled_reply_leak.py",
        "fixture_wire_typo.py",
        "fixture_wire_arity.py",
        "fixture_wire_none_reply.py",
        "fixture_actor_lease_leak.py",
        "fixture_tenant_ops_leak.py",
        "fixture_proxy_ops_leak.py",
        "fixture_observe_ops_leak.py",
        "fixture_recovery_ops_leak.py",
        "fixture_preempt_ops_leak.py",
    ):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "ray_tpu.devtools.lint",
                "--no-baseline",
                os.path.join(FIXTURES, fx),
            ],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr


# ---------------------------------------------------------- whole-tree gate


def test_whole_tree_zero_nonbaselined_and_fast():
    """The repo lints clean modulo the checked-in baseline, in < 30 s."""
    t0 = time.monotonic()
    findings = lint_paths(
        [os.path.join(REPO, "ray_tpu")],
        root=REPO,
        config={"protocol_doc": "docs/PROTOCOL.md"},
        full_tree=True,
    )
    elapsed = time.monotonic() - t0
    base = baseline_mod.load(os.path.join(REPO, "tools", "tpulint_baseline.json"))
    new, accepted, stale = baseline_mod.split(findings, base)
    assert new == [], "un-baselined findings:\n" + "\n\n".join(
        f.render() for f in new
    )
    assert stale == [], (
        "stale baseline entries (finding fixed — delete them): "
        + ", ".join(e["fingerprint"] for e in stale)
    )
    assert elapsed < 30.0, f"tpulint took {elapsed:.1f}s on the tree"


def test_cli_stale_baseline_fails_full_run(tmp_path):
    """A leftover baseline fingerprint would silently re-accept a
    reintroduced bug — full runs must fail until it is deleted."""
    base = json.load(open(os.path.join(REPO, "tools", "tpulint_baseline.json")))
    base["findings"].append(
        {
            "fingerprint": "deadbeefdeadbeef",
            "check": "lock-order",
            "file": "ray_tpu/ghost.py",
            "qualname": "ray_tpu.ghost.gone",
            "line": 1,
            "message": "finding that no longer exists",
            "reason": "test stale entry",
        }
    )
    doctored = tmp_path / "baseline.json"
    doctored.write_text(json.dumps(base))
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "ray_tpu.devtools.lint",
            "--baseline",
            str(doctored),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "stale" in proc.stdout


def test_cli_whole_tree_exit_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lint"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 new" in proc.stdout


def test_cli_changed_only_shares_baseline():
    """--changed-only lints only the diff vs merge-base(HEAD, main) but
    matches findings against the SAME full-tree baseline (slice fingerprints
    must equal full-tree fingerprints), and stays fast."""
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "ray_tpu.devtools.lint", "--changed-only"],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 30.0
    # stale entries from out-of-slice files are reported, never fatal
    assert "new" in proc.stdout or "no changed files" in proc.stdout


def test_cli_write_baseline_refuses_slices(tmp_path):
    """--write-baseline on a slice would truncate the shared full-tree
    baseline (reviewed reasons included) — it must refuse."""
    for argv in (
        ["--changed-only", "--write-baseline"],
        [os.path.join(FIXTURES, "fixture_clean.py"), "--write-baseline"],
    ):
        proc = subprocess.run(
            [sys.executable, "-m", "ray_tpu.devtools.lint", *argv],
            cwd=REPO,
            capture_output=True,
            text=True,
            timeout=120,
        )
        assert proc.returncode == 2, (argv, proc.stdout, proc.stderr)
        assert "full-tree" in proc.stderr
    # an explicit standalone baseline file is still allowed
    proc = subprocess.run(
        [
            sys.executable, "-m", "ray_tpu.devtools.lint",
            os.path.join(FIXTURES, "fixture_clean.py"),
            "--write-baseline", "--baseline", str(tmp_path / "b.json"),
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_slice_fingerprints_match_full_tree():
    """The module-naming rule makes a single-file slice produce the same
    qualnames (hence fingerprints) as the full-tree run — the property
    --changed-only's baseline sharing rests on."""
    target = os.path.join(REPO, "ray_tpu", "_private", "worker_runtime.py")
    slice_f = lint_paths([target], root=REPO)
    base = baseline_mod.load(os.path.join(REPO, "tools", "tpulint_baseline.json"))
    chaos = [f for f in slice_f if "_chaos_table" in f.message]
    assert chaos, "expected the baselined chaos-table finding in the slice"
    assert chaos[0].fingerprint in base


def test_lint_sees_through_locktrace_registration():
    """register_lock() wrapping must not blind the analyzer to core locks."""
    from ray_tpu.devtools.lint import analyze, discover

    project = discover([os.path.join(REPO, "ray_tpu")], root=REPO)
    analyze(project)
    for lock_id in (
        "ray_tpu._private.controller.Controller.lock",
        "ray_tpu._private.worker_runtime.WorkerRuntime.actor_exec_locks[*]",
        "ray_tpu._private.object_store.MemoryStore._lock",
        "ray_tpu.serve.controller.ServeControllerActor._lock",
    ):
        assert lock_id in project.locks, lock_id


# ------------------------------------------------------ locktrace + watchdog


def test_locktrace_owner_table():
    import threading

    from ray_tpu._private import locktrace

    rlock = locktrace.register_lock("t_owner.rlock", threading.RLock())
    cv = locktrace.register_lock("t_owner.cv", threading.Condition(rlock))
    ev = locktrace.register_lock("t_owner.event", threading.Event())
    release = threading.Event()
    acquired = threading.Event()

    def holder():
        with rlock:
            acquired.set()
            release.wait(5.0)

    t = threading.Thread(target=holder, name="t-owner-holder", daemon=True)
    t.start()
    assert acquired.wait(5.0)
    try:
        table = locktrace.owner_table()
        assert "t-owner-holder" in table["t_owner.rlock"]
        assert "t-owner-holder" in table["t_owner.cv"]  # cv reports wrapped lock
        assert table["t_owner.event"] == "event:cleared"
        dump = locktrace.dump_all()
        assert "t-owner-holder" in dump and "registered lock owners" in dump
    finally:
        release.set()
        t.join(timeout=5.0)
    assert "unlocked" in locktrace.owner_table()["t_owner.rlock"]


def test_locktrace_name_collision_suffixes():
    import threading

    from ray_tpu._private import locktrace

    a = threading.Lock()
    b = threading.Lock()
    locktrace.register_lock("t_collide.lock", a)
    locktrace.register_lock("t_collide.lock", b)
    table = locktrace.owner_table()
    assert "t_collide.lock" in table and "t_collide.lock#2" in table


def test_watchdog_dumps_lock_owner_table(tmp_path):
    """End-to-end: a hung test holding a registered lock AND a live rt_*
    shm segment times out, and the watchdog prints the thread stacks, the
    lock owner table, and the live-resource table (the leaked segment by
    name) to stderr."""
    test_src = textwrap.dedent(
        """
        import threading
        from multiprocessing import shared_memory
        from ray_tpu._private import locktrace

        def test_hangs_holding_registered_lock():
            lock = locktrace.register_lock("wd.hung_lock", threading.Lock())
            seg = shared_memory.SharedMemory(
                create=True, size=64, name="rt_wd_leaked_segment"
            )
            try:
                with lock:
                    threading.Event().wait(30)  # > the 2 s watchdog below
            finally:
                seg.close()
                seg.unlink()
        """
    )
    (tmp_path / "test_wd.py").write_text(test_src)
    env = dict(os.environ, RAY_TPU_TEST_TIMEOUT_S="2", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "pytest",
            str(tmp_path / "test_wd.py"),
            "-q",
            "-p",
            "no:cacheprovider",
            # tmp_path is outside tests/, so load the watchdog conftest as a
            # plugin explicitly
            "-p",
            "tests.conftest",
        ],
        cwd=REPO,
        capture_output=True,
        text=True,
        timeout=120,
        env=env,
    )
    assert proc.returncode != 0
    # the inner pytest captures stderr and replays it in the failure report,
    # so search the combined output
    out = proc.stdout + proc.stderr
    assert "exceeded" in out
    assert "registered lock owners" in out, out[-2000:]
    assert "wd.hung_lock" in out, out[-2000:]
    assert "locked" in out, out[-2000:]
    assert "live resources" in out, out[-2000:]
    assert "rt_wd_leaked_segment" in out, out[-3000:]


def test_every_baseline_entry_has_a_real_reason():
    with open(os.path.join(REPO, "tools", "tpulint_baseline.json")) as f:
        data = json.load(f)
    assert data["findings"], "baseline should record the accepted debt"
    for e in data["findings"]:
        assert e["reason"] and e["reason"] != baseline_mod.DEFAULT_REASON, (
            f"baseline entry {e['fingerprint']} needs a reviewed reason"
        )
        assert e["check"] in CHECKS
