"""Train library tests.

Coverage modeled on the reference's ``python/ray/train/tests``
(``test_data_parallel_trainer.py``, ``test_checkpoint_manager.py``,
``test_session.py``): trainer contract, report/checkpoint round-trip,
failure retries, top-k retention, multi-rank context wiring.
"""

import os

import pytest

import ray_tpu
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train._internal.checkpoint_manager import CheckpointManager

pytestmark = pytest.mark.timeout(300) if hasattr(pytest.mark, "timeout") else []


@pytest.fixture
def storage(tmp_path):
    return str(tmp_path / "results")


def test_single_worker_fit(ray_start_thread, storage):
    def loop(config):
        import ray_tpu.train as train

        for i in range(config["steps"]):
            train.report({"loss": 1.0 / (i + 1), "step": i})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] == pytest.approx(1.0 / 3)
    assert len(result.metrics_history) == 3


def test_multi_worker_context(ray_start_thread, storage):
    def loop():
        import ray_tpu.train as train

        ctx = train.get_context()
        train.report({"rank": ctx.get_world_rank(), "ws": ctx.get_world_size()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="t2", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is None
    # controller reads rank-0's reports
    assert result.metrics["rank"] == 0
    assert result.metrics["ws"] == 2


def test_checkpoint_report_and_restore(ray_start_thread, storage):
    def loop(config):
        import ray_tpu.train as train

        chk = train.get_checkpoint()
        start = chk.to_dict()["step"] + 1 if chk else 0
        for i in range(start, start + 2):
            train.report(
                {"step": i}, checkpoint=Checkpoint.from_dict({"step": i})
            )

    trainer = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3", storage_path=storage),
    )
    r1 = trainer.fit()
    assert r1.checkpoint is not None
    assert r1.checkpoint.to_dict()["step"] == 1

    trainer2 = JaxTrainer(
        loop,
        train_loop_config={},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t3b", storage_path=storage),
        resume_from_checkpoint=r1.checkpoint,
    )
    r2 = trainer2.fit()
    assert r2.checkpoint.to_dict()["step"] == 3


def test_failure_no_retry(ray_start_thread, storage):
    def loop():
        raise RuntimeError("worker exploded")

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t4", storage_path=storage),
    )
    result = trainer.fit()
    assert result.error is not None
    assert "worker exploded" in result.error


def test_failure_retry_then_succeed(ray_start_thread, storage, tmp_path):
    marker = str(tmp_path / "attempted")

    def loop(config):
        import ray_tpu.train as train

        if not os.path.exists(config["marker"]):
            with open(config["marker"], "w") as f:
                f.write("x")
            raise RuntimeError("transient")
        train.report({"ok": 1})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"marker": marker},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="t5",
            storage_path=storage,
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["ok"] == 1


def test_stop_criteria(ray_start_thread, storage):
    def loop():
        import ray_tpu.train as train

        for i in range(1000):
            train.report({"step": i})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t6", storage_path=storage, stop={"step": 5}),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] >= 5


def test_dataset_shard_plain_iterable(ray_start_thread, storage):
    def loop():
        import ray_tpu.train as train

        shard = train.get_dataset_shard("train")
        total = sum(shard)
        train.report({"total": total})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t7", storage_path=storage),
        datasets={"train": [1, 2, 3, 4]},
    )
    result = trainer.fit()
    assert result.metrics["total"] == 10


def test_checkpoint_manager_topk(tmp_path):
    mgr = CheckpointManager(
        CheckpointConfig(
            num_to_keep=2,
            checkpoint_score_attribute="acc",
            checkpoint_score_order="max",
        )
    )
    paths = []
    for i, acc in enumerate([0.1, 0.9, 0.5, 0.3]):
        d = str(tmp_path / f"chk{i}")
        os.makedirs(d)
        paths.append(d)
        mgr.register(Checkpoint(d), {"acc": acc})
    kept = {tc.checkpoint.path for tc in mgr.tracked}
    # top-2 by acc are chk1 (0.9) and chk2 (0.5); latest (chk3) is protected
    assert os.path.abspath(paths[1]) in kept
    assert mgr.best_checkpoint().path == os.path.abspath(paths[1])
    assert not os.path.exists(paths[0])


def test_pytree_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.train import restore_pytree, save_pytree

    tree = {"w": jnp.ones((4, 4)), "b": np.arange(3), "nested": {"s": jnp.float32(2.0)}}
    d = str(tmp_path / "pt")
    os.makedirs(d)
    save_pytree(tree, d)
    out = restore_pytree(d)
    np.testing.assert_array_equal(out["w"], np.ones((4, 4)))
    np.testing.assert_array_equal(out["b"], np.arange(3))
    assert float(out["nested"]["s"]) == 2.0


def test_elastic_midrun_resize(ray_start_cluster, storage):
    """Elastic training resizes MID-RUN: the group starts at available
    capacity (>= min_workers), and when a node joins, the controller
    restarts the gang at the larger size from the latest checkpoint —
    without charging the failure budget (reference:
    ``train/v2/_internal/execution/scaling_policy/``)."""
    import threading
    import time

    # head has 4 CPUs; thread-mode driver needs none. Capacity = 4 workers?
    # make each worker cost 2 CPUs so only 2 fit initially.
    def loop():
        import time as _t

        import ray_tpu.train as train

        chk = train.get_checkpoint()
        start = chk.to_dict()["i"] if chk else 0
        ws = train.get_context().get_world_size()
        for i in range(start, 200):
            train.report(
                {"i": i, "world_size": ws},
                checkpoint=(
                    Checkpoint.from_dict({"i": i + 1})
                    if train.get_context().get_world_rank() == 0
                    else None
                ),
            )
            _t.sleep(0.05)

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(
            num_workers=4,
            min_workers=2,
            resources_per_worker={"CPU": 2},
            # the grown gang spans nodes: STRICT_PACK (one-ICI-domain
            # default) cannot place 8 CPUs on a 4-CPU node
            placement_strategy="PACK",
        ),
        run_config=RunConfig(name="elastic", storage_path=storage),
    )
    box = {}

    def run():
        box["result"] = trainer.fit()

    t = threading.Thread(target=run)
    t.start()
    # let the 2-worker group make checkpointed progress, then add capacity
    time.sleep(2.0)
    ray_start_cluster.add_node(num_cpus=4)
    t.join(timeout=120)
    assert not t.is_alive(), "trainer did not finish"
    result = box["result"]
    assert result.error is None, result.error
    sizes = {m.get("world_size") for m in result.metrics_history}
    assert 2 in sizes, sizes  # started at available capacity
    assert 4 in sizes, sizes  # grew to num_workers after the node joined
    # resumed from checkpoint, not from scratch: every step index observed
    # at most twice (once per attempt boundary), and the final step is 59
    assert result.metrics_history[-1]["i"] == 199
    controller = trainer._controller
    assert controller.num_resizes >= 1
    assert controller.failure_policy.failures == 0
