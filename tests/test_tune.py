"""Tune tests: variant generation, Tuner.fit, schedulers (ASHA/PBT), retries.

Coverage modeled on the reference's ``tune/tests`` (``test_tune_*.py``,
``test_trial_scheduler.py``, ``test_trial_scheduler_pbt.py``).
"""

import os

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, FailureConfig, RunConfig
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.search.basic_variant import generate_variants

pytestmark = pytest.mark.timeout(300) if hasattr(pytest.mark, "timeout") else []


@pytest.fixture
def run_cfg(tmp_path):
    def make(**kw):
        return RunConfig(storage_path=str(tmp_path / "results"), **kw)

    return make


def test_generate_variants_grid_and_random():
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "mom": tune.uniform(0.0, 1.0),
        "nested": {"units": tune.grid_search([32, 64])},
        "fixed": "adam",
    }
    variants = list(generate_variants(space, num_samples=2, seed=0))
    assert len(variants) == 8  # 2 grid * 2 grid * 2 samples
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    assert {v["nested"]["units"] for v in variants} == {32, 64}
    assert all(0.0 <= v["mom"] <= 1.0 for v in variants)
    assert all(v["fixed"] == "adam" for v in variants)


def test_domains_sample_in_range():
    import random

    rng = random.Random(0)
    assert 1 <= tune.randint(1, 10).sample(rng) < 10
    assert 1e-4 <= tune.loguniform(1e-4, 1e-1).sample(rng) <= 1e-1
    assert tune.choice(["a", "b"]).sample(rng) in ("a", "b")
    v = tune.quniform(0, 1, 0.25).sample(rng)
    assert abs(v / 0.25 - round(v / 0.25)) < 1e-9


def test_tuner_grid_fit(ray_start_thread, run_cfg):
    def trainable(config):
        tune.report({"score": config["x"] ** 2})

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2, 3])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=run_cfg(name="grid"),
    ).fit()
    assert len(results) == 3
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.metrics["score"] == 9
    assert best.config["x"] == 3


def test_tuner_min_mode_and_num_samples(ray_start_thread, run_cfg):
    def trainable(config):
        tune.report({"loss": abs(config["x"] - 0.5)})

    results = Tuner(
        trainable,
        param_space={"x": tune.uniform(0, 1)},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=5, seed=1),
        run_config=run_cfg(name="rand"),
    ).fit()
    assert len(results) == 5
    best = results.get_best_result()
    assert best.metrics["loss"] == min(r.metrics["loss"] for r in results)


def test_asha_stops_bad_trials(ray_start_thread, run_cfg):
    def trainable(config):
        import time

        for i in range(20):
            tune.report({"acc": config["quality"] * (i + 1)})
            time.sleep(0.02)  # realistic cadence so polls interleave

    results = Tuner(
        trainable,
        # strong trials first so rung records exist when weak ones arrive
        param_space={"quality": tune.grid_search([2.0, 1.0, 0.02, 0.01])},
        tune_config=TuneConfig(
            metric="acc",
            mode="max",
            scheduler=tune.ASHAScheduler(
                max_t=20, grace_period=2, reduction_factor=2
            ),
            max_concurrent_trials=4,
        ),
        run_config=run_cfg(name="asha"),
    ).fit()
    assert results.num_errors == 0
    best = results.get_best_result()
    assert best.config["quality"] == 2.0
    # weak trials should have been cut before 20 iterations
    weak = [r for r in results if r.config["quality"] <= 0.02]
    assert any(len(r.metrics_history) < 20 for r in weak)


def test_pbt_exploits_and_mutates(ray_start_thread, run_cfg):
    def trainable(config):
        import time

        chk = tune.get_checkpoint()
        score = chk.to_dict()["score"] if chk else 0.0
        for _ in range(30):
            score += config["lr"]
            tune.report(
                {"score": score, "lr": config["lr"]},
                checkpoint=Checkpoint.from_dict({"score": score}),
            )
            time.sleep(0.02)  # realistic cadence so PBT sees both trials

    results = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.001, 1.0])},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=tune.PopulationBasedTraining(
                perturbation_interval=5,
                hyperparam_mutations={"lr": [0.001, 1.0]},
                quantile_fraction=0.5,
                seed=0,
            ),
            max_concurrent_trials=2,
        ),
        run_config=run_cfg(name="pbt"),
    ).fit()
    assert results.num_errors == 0
    # the weak trial must have exploited the strong one's checkpoint: its
    # final score reflects the donor's progress, impossible from lr=0.001 alone
    scores = sorted(r.metrics.get("score", 0) for r in results)
    assert scores[0] > 0.001 * 35


def test_trial_failure_retry(ray_start_thread, run_cfg, tmp_path):
    marker = str(tmp_path / "failed_once")

    def trainable(config):
        if config["x"] == 2 and not os.path.exists(marker):
            open(marker, "w").write("x")
            raise RuntimeError("flaky trial")
        tune.report({"score": config["x"]})

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=run_cfg(name="retry", failure_config=FailureConfig(max_failures=1)),
    ).fit()
    assert results.num_errors == 0
    assert results.get_best_result().metrics["score"] == 2


def test_trial_failure_exhausted(ray_start_thread, run_cfg):
    def trainable(config):
        raise RuntimeError("always broken")

    results = Tuner(
        trainable,
        param_space={"x": tune.grid_search([1])},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=run_cfg(name="fail"),
    ).fit()
    assert results.num_errors == 1
    assert "always broken" in results.errors[0]


def test_with_parameters_and_resources(ray_start_thread, run_cfg):
    big = list(range(100))

    def trainable(config, data=None):
        tune.report({"n": len(data) + config["x"]})

    wrapped = tune.with_resources(
        tune.with_parameters(trainable, data=big), {"CPU": 1}
    )
    results = Tuner(
        wrapped,
        param_space={"x": tune.grid_search([1])},
        tune_config=TuneConfig(metric="n", mode="max"),
        run_config=run_cfg(name="wp"),
    ).fit()
    assert results.get_best_result().metrics["n"] == 101


def test_trainer_as_trainable(ray_start_thread, run_cfg):
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        import ray_tpu.train as train

        train.report({"val": config["lr"] * 10})

    trainer = JaxTrainer(
        loop,
        train_loop_config={"lr": 0.0},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=run_cfg(name="inner"),
    )
    results = Tuner(
        trainer,
        param_space={"train_loop_config": {"lr": tune.grid_search([1.0, 2.0])}},
        tune_config=TuneConfig(metric="val", mode="max", max_concurrent_trials=1),
        run_config=run_cfg(name="sweep"),
    ).fit()
    assert results.num_errors == 0, results.errors
    assert results.get_best_result().metrics["val"] == 20.0


def test_hyperband_sync_brackets(ray_start_thread, run_cfg):
    """True synchronous HyperBand: cohort pauses at rungs, exact top-1/eta
    cut, survivors resume from their checkpoints, losers stop early."""
    iters_seen = {}

    def trainable(config):
        chk = tune.get_checkpoint()
        start = chk.to_dict()["i"] if chk else 0
        for i in range(start, 100):
            tune.report(
                {"score": config["q"] * (i + 1), "q": config["q"]},
                checkpoint=Checkpoint.from_dict({"i": i + 1}),
            )

    qualities = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0, 9.0]
    results = Tuner(
        trainable,
        param_space={"q": tune.grid_search(qualities)},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=tune.HyperBandScheduler(max_t=9, reduction_factor=3),
            max_concurrent_trials=3,
        ),
        run_config=run_cfg(name="hb"),
    ).fit()
    assert results.num_errors == 0
    # bad trials must be cut early, good ones trained longer
    iters_by_q = {
        r.metrics.get("q"): r.metrics.get("training_iteration", 0) for r in results
    }
    best_iters = iters_by_q[9.0]
    worst_iters = min(v for v in iters_by_q.values())
    assert best_iters > worst_iters, iters_by_q
    # total budget must be well under running everything to max_t
    total = sum(iters_by_q.values())
    assert total < 9 * 9, (total, iters_by_q)


def test_pb2_gp_explore_within_bounds(ray_start_thread, run_cfg):
    """PB2: exploit copies the donor checkpoint; GP-UCB explore proposes lr
    strictly inside the declared bounds."""
    seen_lrs = []

    def trainable(config):
        import time as _t

        chk = tune.get_checkpoint()
        score = chk.to_dict()["score"] if chk else 0.0
        for _ in range(25):
            score += config["lr"]
            tune.report(
                {"score": score, "lr": config["lr"]},
                checkpoint=Checkpoint.from_dict({"score": score}),
            )
            _t.sleep(0.02)

    results = Tuner(
        trainable,
        param_space={"lr": tune.grid_search([0.01, 0.9])},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=tune.PB2(
                perturbation_interval=5,
                hyperparam_bounds={"lr": [0.001, 1.0]},
                quantile_fraction=0.5,
                seed=0,
            ),
            max_concurrent_trials=2,
        ),
        run_config=run_cfg(name="pb2"),
    ).fit()
    assert results.num_errors == 0
    # the weak trial exploited the strong one's checkpoint
    scores = sorted(r.metrics.get("score", 0) for r in results)
    assert scores[0] > 0.01 * 30
    # every explored lr respects the bounds
    for r in results:
        assert 0.001 <= r.metrics.get("lr", 0.5) <= 1.0


def test_pb2_gp_regressor_sanity():
    """The internal GP interpolates a smooth function and shrinks variance
    at observed points."""
    import numpy as np

    from ray_tpu.tune.schedulers.pb2 import _GP

    rng = np.random.default_rng(0)
    X = rng.uniform(size=(30, 2))
    y = np.sin(3 * X[:, 0]) + 0.5 * X[:, 1]
    y_n = (y - y.mean()) / y.std()
    gp = _GP()
    gp.fit(X, y_n)
    mu_obs, sd_obs = gp.predict(X)
    assert float(np.abs(mu_obs - y_n).mean()) < 0.1
    assert float(sd_obs.mean()) < 0.3
    mu_far, sd_far = gp.predict(np.array([[5.0, 5.0]]))
    assert sd_far[0] > 0.9  # prior variance far from data


def test_gp_searcher_beats_random_on_smooth_objective(ray_start_thread, run_cfg):
    """Native GP-UCB searcher: on a smooth 1-D objective it concentrates
    suggestions near the optimum after the random warmup."""

    def trainable(config):
        x = config["x"]
        tune.report({"score": -((x - 0.7) ** 2)})

    results = Tuner(
        trainable,
        param_space={"x": tune.uniform(0.0, 1.0)},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            search_alg=tune.GPSearcher(n_initial=4, seed=0),
            num_samples=20,
            max_concurrent_trials=1,  # sequential: the GP sees each result
        ),
        run_config=run_cfg(name="gp"),
    ).fit()
    assert results.num_errors == 0
    xs = [r.config["x"] for r in results]
    assert len(xs) == 20
    # post-warmup suggestions concentrate near the optimum at 0.7
    post = xs[8:]
    near = [x for x in post if abs(x - 0.7) < 0.15]
    assert len(near) >= len(post) // 2, xs
    best = results.get_best_result(metric="score", mode="max")
    assert abs(best.config["x"] - 0.7) < 0.1, best.config


def test_tuner_restore_after_driver_death(tmp_path):
    """Kill the driver mid-sweep; Tuner.restore(dir) finishes the remaining
    trials and keeps completed results (reference: Tuner.restore over
    experiment snapshots, tune/execution/tune_controller.py:68)."""
    import json
    import subprocess
    import sys

    exp_root = tmp_path / "results"
    marker = tmp_path / "progress"
    marker.mkdir()
    code = f"""
import os, time
import ray_tpu
from ray_tpu import tune
from ray_tpu.train import Checkpoint, RunConfig
from ray_tpu.tune import TuneConfig, Tuner

ray_tpu.init(num_cpus=2, mode="thread")

def trainable(config):
    for i in range(40):
        open(os.path.join({str(marker)!r}, f"{{config['x']}}-{{i}}"), "w").close()
        tune.report(
            {{"score": config["x"] * (i + 1), "training_iteration": i + 1}},
            checkpoint=Checkpoint.from_pytree({{"i": i, "x": config["x"]}}),
        )
        time.sleep(0.3)

Tuner(
    trainable,
    param_space={{"x": tune.grid_search([1, 2, 3, 4])}},
    tune_config=TuneConfig(metric="score", mode="max", max_concurrent_trials=2),
    run_config=RunConfig(name="resume-exp", storage_path={str(exp_root)!r}),
).fit()
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen([sys.executable, "-c", code], env=env)
    # wait until the sweep is visibly mid-flight, then kill -9 the driver
    import time as _time

    deadline = _time.monotonic() + 120
    while _time.monotonic() < deadline:
        if len(list(marker.iterdir())) >= 4:
            break
        _time.sleep(0.2)
    proc.kill()
    proc.wait()

    exp_dir = exp_root / "resume-exp"
    assert (exp_dir / "experiment_state.pkl").exists()

    # resume in a fresh "driver" (this process)
    ray_tpu.init(num_cpus=4, mode="thread", ignore_reinit_error=True)
    try:
        def trainable(config):
            for i in range(3):  # shorter finish: just prove trials complete
                tune.report({"score": config["x"] * 100 + i,
                             "training_iteration": i + 1})

        results = Tuner.restore(str(exp_dir), trainable).fit()
        assert len(results) == 4  # the full grid, restored + newly created
        assert all(r.error is None for r in results)
        xs = sorted(r.config["x"] for r in results)
        assert xs == [1, 2, 3, 4]
    finally:
        ray_tpu.shutdown()


def test_broadcast_from_rank_zero_and_barrier(tmp_path):
    """Gang workers fan out rank 0's value (reference:
    train/collective/collectives.py)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ray_tpu.init(num_cpus=4, mode="thread", ignore_reinit_error=True)
    try:
        def loop():
            from ray_tpu.train import collective
            from ray_tpu.train.session import get_context

            ctx = get_context()
            value = collective.broadcast_from_rank_zero(
                {"seed": 1234} if ctx.world_rank == 0 else None
            )
            collective.barrier()
            assert value == {"seed": 1234}
            from ray_tpu import train as train_api
            train_api.report({"seed": value["seed"], "rank": ctx.world_rank})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(storage_path=str(tmp_path / "bc")),
        )
        result = trainer.fit()
        assert result.error is None
    finally:
        ray_tpu.shutdown()
