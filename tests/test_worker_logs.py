"""Worker log capture + driver log streaming.

Reference: the per-session log dir (``python/ray/_private/node.py``), the
log monitor tailing per-worker files to the driver
(``python/ray/_private/log_monitor.py``), and ``ray logs`` /
``list_logs`` (``dashboard/modules/log/``). Contract points:

- a ``print`` inside a task running in a REMOTE worker process appears on
  the driver's console, prefixed with the worker identity
- a dead worker's captured output stays fetchable (files outlive processes)
- the state API exposes a logs source (list + fetch + ring buffer)
"""

import json
import os
import sys
import time

import pytest

import ray_tpu
from ray_tpu.util.state import api as st

pytestmark = pytest.mark.timeout(300) if hasattr(pytest.mark, "timeout") else []


def _poll_stdout(capsys, needle: str, timeout: float = 20.0) -> str:
    """Accumulate captured stdout until ``needle`` appears."""
    acc = ""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = capsys.readouterr()
        acc += out.out + out.err
        if needle in acc:
            return acc
        time.sleep(0.25)
    return acc


def test_task_print_streams_to_driver(ray_start_process, capsys):
    @ray_tpu.remote
    def chatty():
        print("hello-from-worker-60f1")
        return os.getpid()

    pid = ray_tpu.get(chatty.remote(), timeout=60)
    assert pid != os.getpid()  # really another process
    acc = _poll_stdout(capsys, "hello-from-worker-60f1")
    assert "hello-from-worker-60f1" in acc, f"captured: {acc[-2000:]!r}"
    # the line carries the worker-identity prefix
    line = next(l for l in acc.splitlines() if "hello-from-worker-60f1" in l)
    assert "pid=" in line and "ip=" in line


def test_actor_print_carries_class_label(ray_start_process, capsys):
    @ray_tpu.remote
    class Talker:
        def speak(self):
            print("talker-says-ba5e")
            return True

    t = Talker.remote()
    assert ray_tpu.get(t.speak.remote(), timeout=60)
    acc = _poll_stdout(capsys, "talker-says-ba5e")
    line = next(l for l in acc.splitlines() if "talker-says-ba5e" in l)
    assert "Talker" in line


def test_dead_worker_logs_fetchable(ray_start_process):
    @ray_tpu.remote
    class Doomed:
        def shout(self):
            print("last-words-c0de")
            sys.stdout.flush()
            return True

    d = Doomed.remote()
    assert ray_tpu.get(d.shout.remote(), timeout=60)
    time.sleep(0.5)  # let the line reach the file
    ray_tpu.kill(d)
    time.sleep(1.0)
    # find the worker by scanning captured logs — it is DEAD now
    found = None
    for row in st.list_logs():
        text = st.get_log(row["worker_id"], source="out")
        if "last-words-c0de" in text:
            found = row
            break
    assert found is not None, "dead worker's output not fetchable"
    # ring-buffer source agrees
    lines = [e["line"] for e in st.tail_cluster_logs()]
    assert any("last-words-c0de" in l for l in lines)


def test_state_api_list_logs_shape(ray_start_process):
    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote(), timeout=60)
    rows = st.list_logs()
    assert rows, "no log files listed"
    row = rows[0]
    assert "worker_id" in row and "ip" in row


def _native_available():
    from ray_tpu._native import plasma

    return plasma.available()


@pytest.mark.slow
@pytest.mark.skipif(
    not _native_available(), reason="node agents require the native store"
)
def test_remote_node_print_streams_to_driver(tmp_path, capsys):
    """The done-bar: a print inside a task on a REMOTE agent node appears on
    the driver's console (agent tails → head prints), and the dead remote
    worker's output is fetchable through the head."""
    from tests.test_node_agent import _AgentCluster

    ray_tpu.init(num_cpus=2, mode="process", config={"tcp_port": 0})
    cluster = _AgentCluster(tmp_path)
    try:
        cluster.add_agent("a1", {"CPU": 2, "remote_only": 2})

        @ray_tpu.remote(resources={"remote_only": 1})
        def remote_chatty():
            print("hello-from-remote-node-7e11")
            return os.environ.get("RAY_TPU_ARENA")

        arena = ray_tpu.get(remote_chatty.remote(), timeout=120)
        head_arena = getattr(cluster.controller.plasma, "arena_name", None)
        assert arena is not None and arena != head_arena  # ran on the agent
        acc = _poll_stdout(capsys, "hello-from-remote-node-7e11", timeout=30)
        assert "hello-from-remote-node-7e11" in acc, f"captured: {acc[-2000:]!r}"
        # fetch over the agent control channel by worker-id prefix
        found = ""
        for row in st.list_logs():
            if row.get("ip") not in ("local", None):
                try:
                    text = st.get_log(row["worker_id"], source="out")
                except (ValueError, TimeoutError):
                    continue
                if "hello-from-remote-node-7e11" in text:
                    found = text
                    break
        assert found, "remote worker's file not fetchable through the head"
    finally:
        cluster.shutdown()
        ray_tpu.shutdown()
